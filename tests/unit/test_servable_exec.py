"""Signature execution path: transfer casts, overlapped output fetch.

Covers the serving-hot-path behaviors the reference leaves to
Session::Run + Tensor conversion (predict_util.cc:89-215): host-side
transfer-dtype casts, device placement of formed batches, and the
single-round device->host fetch of requested outputs.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from min_tfs_client_tpu.servables.servable import (
    Signature,
    TensorSpec,
    fetch_outputs,
)


def _echo_sig(**kw):
    def fn(inputs):
        x = jnp.asarray(inputs["x"])
        return {"y": x * 2, "dtype_code": jnp.zeros((x.shape[0],), x.dtype)}

    return Signature(
        fn=fn,
        inputs={"x": TensorSpec(np.float32, (None, 4))},
        outputs={"y": TensorSpec(np.float32, (None, 4)),
                 "dtype_code": TensorSpec(np.float32, (None,))},
        batch_buckets=(2, 4, 8),
        **kw,
    )


class TestTransferCasts:
    def test_cast_applied_before_device(self):
        sig = _echo_sig(transfer_casts={"x": "bfloat16"})
        out = sig.run({"x": np.ones((2, 4), np.float32)})
        # The fn saw bf16 inputs: its passthrough dtype output is bf16.
        assert out["dtype_code"].dtype == jnp.bfloat16

    def test_values_survive_cast_and_padding(self):
        sig = _echo_sig(transfer_casts={"x": "bfloat16"})
        x = np.arange(12, dtype=np.float32).reshape(3, 4)
        out = sig.run({"x": x})  # batch 3 -> bucket 4, sliced back
        assert out["y"].shape == (3, 4)
        np.testing.assert_allclose(out["y"].astype(np.float32), x * 2,
                                   rtol=2e-2)

    def test_unknown_alias_rejected_at_build(self):
        with pytest.raises(ValueError, match="not .*signature inputs"):
            _echo_sig(transfer_casts={"nope": "bfloat16"})

    def test_bad_dtype_rejected_at_build(self):
        with pytest.raises(TypeError):
            _echo_sig(transfer_casts={"x": "bfloat99"})


class TestFetchOutputs:
    def test_slices_padded_batch(self):
        outs = {"a": jnp.ones((8, 3)), "b": jnp.zeros((8,))}
        got = fetch_outputs(outs, batch=5)
        assert got["a"].shape == (5, 3)
        assert got["b"].shape == (5,)
        assert isinstance(got["a"], np.ndarray)

    def test_no_slice_when_batch_none(self):
        got = fetch_outputs({"a": jnp.ones((8, 3))}, batch=None)
        assert got["a"].shape == (8, 3)

    def test_scalar_output_untouched(self):
        got = fetch_outputs({"s": jnp.float32(3.5)}, batch=2)
        assert got["s"].shape == ()
        assert got["s"] == np.float32(3.5)

    def test_plain_numpy_passthrough(self):
        # Host signatures produce numpy; fetch must not require jax arrays.
        got = fetch_outputs({"h": np.arange(6).reshape(3, 2)}, batch=2)
        assert got["h"].shape == (2, 2)


class TestBatchedFilterUnion:
    def test_union_of_filters_reaches_signature(self):
        from min_tfs_client_tpu.batching.scheduler import SharedBatchScheduler
        from min_tfs_client_tpu.batching.session import BatchedSignatureRunner

        seen = []
        sig = _echo_sig()
        inner_run = sig.run

        def spy(inputs, output_filter=()):
            seen.append(tuple(output_filter))
            return inner_run(inputs, output_filter)

        sig.run = spy
        sched = SharedBatchScheduler(num_threads=1)
        try:
            runner = BatchedSignatureRunner(
                sig, sched, name="t", max_batch_size=8, batch_timeout_s=0.0)
            out = runner.run({"x": np.ones((2, 4), np.float32)},
                             output_filter=("y",))
            assert set(out) == {"y"}
            # the device execution only fetched the filtered union
            assert seen and seen[-1] == ("y",)
            # a caller with no filter forces a full fetch
            out2 = runner.run({"x": np.ones((2, 4), np.float32)})
            assert set(out2) == {"y", "dtype_code"}
            assert seen[-1] == ()
        finally:
            sched.stop()


class TestUnionRun:
    def _servable(self):
        from min_tfs_client_tpu.servables.servable import (
            CLASSIFY_METHOD_NAME,
            REGRESS_METHOD_NAME,
            Servable,
        )
        from min_tfs_client_tpu.tensor.example_codec import FeatureSpec

        specs = {"x": FeatureSpec(np.float32, (2,))}
        inputs = {"x": TensorSpec(np.float32, (None, 2))}

        def classify_fn(inputs):
            s = jnp.sum(jnp.asarray(inputs["x"]), -1, keepdims=True)
            return {"scores": jnp.concatenate([s, 1 - s], -1)}

        def regress_fn(inputs):
            return {"outputs": jnp.sum(jnp.asarray(inputs["x"]), -1) * 2}

        sigs = {
            "classify": Signature(
                fn=classify_fn, inputs=inputs,
                outputs={"scores": TensorSpec(np.float32, (None, 2))},
                method_name=CLASSIFY_METHOD_NAME, feature_specs=specs,
                batch_buckets=(2, 4)),
            "regress": Signature(
                fn=regress_fn, inputs=inputs,
                outputs={"outputs": TensorSpec(np.float32, (None,))},
                method_name=REGRESS_METHOD_NAME, feature_specs=specs,
                batch_buckets=(2, 4)),
        }
        return Servable("m", 1, sigs)

    def test_one_dispatch_for_signature_union(self, monkeypatch):
        servable = self._servable()
        assert servable.can_run_union(["classify", "regress"])
        # The union path must never fall back to per-signature run().
        monkeypatch.setattr(
            Signature, "run",
            lambda *a, **k: (_ for _ in ()).throw(AssertionError("run()")))
        x = np.array([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]], np.float32)
        out = servable.run_union(["classify", "regress"], {"x": x})
        np.testing.assert_allclose(out["classify"]["scores"][:, 0],
                                   [3.0, 7.0, 11.0])
        np.testing.assert_allclose(out["regress"]["outputs"],
                                   [6.0, 14.0, 22.0])
        # padded to bucket 4, sliced back to the true batch
        assert out["regress"]["outputs"].shape == (3,)
        assert len(servable._union_jits) == 1

    def test_union_ineligible_when_inputs_differ(self):
        servable = self._servable()
        servable.signatures["regress"].inputs = {
            "other": TensorSpec(np.float32, (None, 2))}
        assert not servable.can_run_union(["classify", "regress"])

    def test_union_ineligible_for_host_signature(self):
        servable = self._servable()
        servable.signatures["classify"].on_host = True
        assert not servable.can_run_union(["classify", "regress"])


class TestUnionThroughHandlers:
    def test_bert_tiny_multi_inference_fuses(self, tmp_path, monkeypatch):
        """BERT's classify/regress share one feature_specs dict, so the
        handler must take the fused single-dispatch path end to end."""
        import jax

        from min_tfs_client_tpu.client import TensorServingClient
        from min_tfs_client_tpu.client.inprocess import unregister_server
        from min_tfs_client_tpu.models import bert, export
        from min_tfs_client_tpu.servables.servable import Servable

        config = bert.BertConfig.tiny()
        params = bert.init_params(jax.random.PRNGKey(0), config)
        base = tmp_path / "bert_tiny"
        export.export_servable(
            base, 1, "bert",
            {"vocab_size": config.vocab_size,
             "hidden_size": config.hidden_size,
             "num_layers": config.num_layers,
             "num_heads": config.num_heads,
             "intermediate_size": config.intermediate_size,
             "max_position": config.max_position},
            params, signature_kwargs={"seq_len": 8})

        union_calls = []
        real_union = Servable.run_union
        monkeypatch.setattr(
            Servable, "run_union",
            lambda self, keys, inputs: (union_calls.append(tuple(keys)),
                                        real_union(self, keys, inputs))[1])
        client = TensorServingClient(f"tpu://{base}")
        try:
            examples = [{"input_ids": np.arange(8, dtype=np.int64)},
                        {"input_ids": np.arange(8, dtype=np.int64) + 1}]
            resp = client.multi_inference_request(
                "bert_tiny", examples,
                methods=[("classify", "tensorflow/serving/classify"),
                         ("regress", "tensorflow/serving/regress")])
        finally:
            unregister_server(f"tpu://{base}")
        assert union_calls == [("classify", "regress")]
        assert len(resp.results) == 2
        classes = resp.results[0].classification_result.classifications
        assert len(classes) == 2 and len(classes[0].classes) == 2
        scores0 = sorted(c.score for c in classes[0].classes)
        assert 0.99 < sum(scores0) < 1.01  # softmax head
        regs = resp.results[1].regression_result.regressions
        assert len(regs) == 2


class TestPlacement:
    def test_string_arrays_pass_through(self):
        # 'O'/'S'/'U'-kind arrays must never reach jax.device_put (it
        # rejects them); LARGE dense arrays come back device-resident.
        big = np.zeros(
            (Signature._PLACE_MIN_BYTES // 4 + 1,), np.float32)
        arrays = {
            "obj": np.array([b"a", b"bc"], object),
            "bytes": np.array([b"ab", b"cdef"]),          # |S4
            "uni": np.array(["x", "yz"]),                 # <U2
            "x": big,
        }
        placed = Signature._place(arrays)
        assert placed["obj"] is arrays["obj"]
        assert placed["bytes"] is arrays["bytes"]
        assert placed["uni"] is arrays["uni"]
        np.testing.assert_array_equal(np.asarray(placed["x"]), arrays["x"])
        assert not isinstance(placed["x"], np.ndarray)  # on device

    def test_small_dense_arrays_skip_explicit_placement(self):
        # Below the size gate the jit arg path transfers just as fast and
        # device_put's Python overhead dominates (~0.2ms/call measured).
        arrays = {"x": np.arange(4, dtype=np.float32)}
        placed = Signature._place(arrays)
        assert placed["x"] is arrays["x"]

    def test_gate_is_on_total_bytes_all_or_none(self):
        # The ~0.2ms cost is per CALL: many medium arrays that together
        # clear the threshold must all take the one overlapped
        # device_put, not each slip under a per-array gate.
        quarter = Signature._PLACE_MIN_BYTES // 4
        arrays = {f"x{i}": np.zeros((quarter // 4 + 1,), np.float32)
                  for i in range(4)}
        placed = Signature._place(arrays)
        for key in arrays:
            assert not isinstance(placed[key], np.ndarray), key
