"""The tests/tpu tier must leave evidence on every exit path, and a later
skip must not erase earlier on-hardware evidence (round-3 Missing #4)."""

from __future__ import annotations

import json

import pytest

from tests.tpu import test_on_device as tier


@pytest.fixture(autouse=True)
def _isolated_artifact(tmp_path, monkeypatch):
    monkeypatch.setattr(tier, "ARTIFACT", tmp_path / "TPU_TIER.json")


def _read():
    return json.loads(tier.ARTIFACT.read_text())


def test_skip_writes_explicit_record():
    tier._persist("skipped", "accelerator wedged: probe timeout")
    blob = _read()
    assert blob["latest"]["status"] == "skipped"
    assert "wedged" in blob["latest"]["detail"]
    assert blob["last_ran"] is None


def test_ran_recorded_with_checks():
    checks = {"flash_attention/plain": {"ok": True, "ms": 12.5}}
    tier._persist("ran", "", checks, platform="tpu")
    blob = _read()
    assert blob["latest"]["status"] == "ran"
    assert blob["latest"]["platform"] == "tpu"
    assert blob["latest"]["checks"] == checks
    assert blob["last_ran"] == blob["latest"]


def test_later_skip_preserves_last_ran():
    checks = {"bucketed_predict": {"ok": True, "ms": 800.0}}
    tier._persist("ran", "", checks, platform="tpu")
    tier._persist("skipped", "no accelerator (cpu backend)")
    blob = _read()
    assert blob["latest"]["status"] == "skipped"
    assert blob["last_ran"]["status"] == "ran"
    assert blob["last_ran"]["checks"] == checks


def test_corrupt_artifact_tolerated():
    tier.ARTIFACT.write_text("garbage")
    tier._persist("skipped", "wedged")
    assert _read()["latest"]["status"] == "skipped"
