"""Test bootstrap: force an 8-device virtual CPU mesh BEFORE jax imports.

This is the "multi-node without a cluster" analogue the survey prescribes
(SURVEY.md §4): every sharding/collective code path runs against 8 virtual
CPU devices, so TP/DP/SP tests execute real XLA collectives with no TPU pod.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
