"""Test bootstrap: force an 8-device virtual CPU mesh BEFORE any jax use.

This is the "multi-node without a cluster" analogue the survey prescribes
(SURVEY.md §4): every sharding/collective code path runs against 8 virtual
CPU devices, so TP/DP/SP tests execute real XLA collectives with no TPU pod.

NOTE: this environment's sitecustomize force-registers the TPU ("axon")
PJRT plugin and rewrites jax_platforms to "axon,cpu" in every process, so
plain JAX_PLATFORMS=cpu is NOT enough — jax.config.update after import is
what actually wins. Benches/TPU runs must not import this conftest.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402  (must follow the env setup above)

jax.config.update("jax_platforms", "cpu")


# ---------------------------------------------------------------------------
# Runtime schedule witness (docs/STATIC_ANALYSIS.md "Runtime witness"):
# concurrency suites opt in with an autouse fixture that requests
# `schedule_witness`; every test then runs with threading.Lock/RLock/
# Condition recording acquisition order and every `# guarded_by:`-declared
# mutation checked held-at-mutation, asserted clean at teardown.

import pytest  # noqa: E402


@pytest.fixture
def schedule_witness():
    from min_tfs_client_tpu.analysis import witness as witness_mod

    wit = witness_mod.ScheduleWitness.for_package()
    wit.install()
    try:
        yield wit
    finally:
        wit.uninstall()
    # After uninstall, so an assertion failure can't leak the patches.
    wit.assert_clean()


# Runtime leak witness (docs/STATIC_ANALYSIS.md "Leak witness"): the
# paged-KV, router-scaleout, and storm-smoke suites arm this autouse;
# every pool that outlives the test must then hold zero net
# pages/slots/pins/conns, and no non-daemon thread may outlive it.


@pytest.fixture
def leak_witness():
    from min_tfs_client_tpu.analysis import witness as witness_mod

    wit = witness_mod.LeakWitness()
    wit.install()
    try:
        yield wit
    finally:
        wit.uninstall()
    # After uninstall, so an assertion failure can't leak the patches.
    wit.assert_no_leaks()
